"""Static metric/span name convention gate (AST-based, dependency-free).

The obs registry enforces the ``area/stage`` naming convention and
unit-conflict detection at *runtime* — but only on the code paths a test
actually executes. This gate walks the source instead: every call to
``counter(`` / ``gauge(`` / ``histogram(`` / ``timed(`` / ``timed_labels(``
/ ``span(`` whose first argument is a string literal is checked against
the convention (lowercase ``area/stage`` segments,
``obs/metrics.py::NAME_RE``), and a name registered with two different
literal ``unit=`` values anywhere in the tree fails as a unit conflict —
the ``record_value``-gauge-under-seconds-keys bug, caught before runtime.

Two cardinality rules ride along (the Prometheus-sanity gate):

- **metric names are exactly ``area/stage``** — a third segment is
  almost always a dimension smuggled into the name (a function name, a
  bucket size) that belongs in a *label*; per-function metrics like the
  compile observatory's must be ``xla/compiles{fn=...}``, never
  ``xla/compiles/my_fn``.
- **no f-string metric names** — ``counter(f'xla/{fn}')`` mints one
  metric per value and defeats both this gate and Prometheus grouping;
  the varying part must be a label. (Span names may stay dynamic:
  they are run-log events, not exposition series.) Other dynamic names
  (plain variables) remain out of scope: the convention applies to the
  literal registration sites, and the runtime guard covers the rest.
- **label keys are registered per area** (``KNOWN_LABELS``): a literal
  ``key=`` kwarg on a registered instrument's ``inc``/``observe``/
  ``set``/``labels`` call must appear in its area's entry, so new
  exposition dimensions (like the batched-xT ``solver``/``n_grids``
  labels) land governed — with their value-cardinality contract noted —
  instead of ad hoc.

Usage: ``python tools/check_metric_names.py [paths...]`` (defaults to
the package plus the repo-root scripts, benchmarks, examples and the
walkthrough — tests are excluded: they intentionally construct invalid
names to exercise the runtime guard). Exits non-zero on findings.
Invoked from ``make lint`` and pinned by ``tests/test_metric_names.py``.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, Iterator, List, Optional, Tuple

#: mirror of socceraction_tpu/obs/metrics.py::NAME_RE (kept dependency-free
#: so the tool runs without importing the package; the test asserts the
#: two stay identical)
NAME_RE = re.compile(r'^[a-z][a-z0-9_]*(/[a-z][a-z0-9_]*)+$')

#: call sites whose first positional string literal is a metric/span name
NAME_TAKING_CALLS = {
    'counter', 'gauge', 'histogram', 'timed', 'timed_labels', 'span',
}

#: The repo's registered metric areas (the segment before the first '/').
#: A new subsystem adds its area here — an unlisted area in a literal
#: registration site fails the gate, so telemetry surfaces cannot appear
#: ungoverned ('train' landed with the fused-train path, PR 3). ``main``
#: enforces this list on every CLI invocation (default targets or
#: explicit paths); ``check_files`` called without ``areas`` — the unit
#: tests' scratch files — checks convention and units only.
KNOWN_AREAS = {
    'bench',  # bench.py headline gauges
    'drift',  # traffic-drift watch (learn/drift.py: PSI/KS vs reference)
    'fleet',  # cross-process aggregation (obs/fleet.py: scrapes/staleness/divergence)
    'learn',  # continuous-learning loop (learn/: ingest/train/shadow/gate)
    'mem',  # device-memory accounting (obs/memory.py)
    'num',  # numeric health: in-dispatch guards + parity probes (obs/numerics.py, obs/parity.py)
    'perf',  # live roofline: achieved FLOPs/bytes + device-idle (obs/perf.py)
    'pipeline',  # store/feed/cache stage timings
    'resil',  # fault injection / retries / breaker / recovery (resil/)
    'scenario',  # counterfactual engine: one-dispatch grid valuation (scenario/)
    'seq',  # sequence-model head: GRU fit/rate/window telemetry (seq/)
    'serve',  # online rating service (batcher/session/registry/service)
    'slo',  # SLO engine: burn rates, budgets, sheds (obs/slo.py)
    'train',  # MLP fit loop + bench training configs
    'vaep',  # rate_batch instrumentation
    'walkthrough',  # narrative-doc demo spans
    'xla',  # compile observatory + profiler traces (obs/xla.py)
    'xt',  # expected-threat fit metrics
}

#: Registered label KEYS per metric area — the cardinality contract's
#: other half. A label key minted at a literal call site
#: (``counter('a/b').inc(1, key=...)``) must appear in its area's entry
#: here, so a new dimension cannot leak into the exposition ungoverned.
#: Values are the label's *keys* only; value cardinality is the caller's
#: contract, noted where it is load-bearing:
#:
#: - ``xt``: ``n_grids`` is the batched-fit fleet size and MUST be
#:   bucketed to powers of two (``xthreat._pow2_bucket``) — an arbitrary
#:   fleet size would mint a series per distinct group count. ``solver``
#:   is dense|matrix-free (sweep structure), ``variant`` the
#:   picard|anderson|anchored|momentum iteration schedule.
#: - sites passing labels via ``**labels`` dicts are out of static
#:   reach; their keys are still registered here as documentation and
#:   the runtime series-budget guard covers the rest.
#: - ``serve``: ``segment`` is the fixed per-request wall decomposition
#:   (queue_wait|pad|dispatch|slice, ``obs/context.py::SEGMENTS``).
#: - ``slo``: ``objective`` values are the configured objective names
#:   (bounded by the SLOConfig, a handful), ``outcome`` good|bad,
#:   ``window`` fast|slow.
#: - ``drift``: ``feature`` values are the monitored packed fields plus
#:   one ``pred_<head>`` per probability head — bounded by DriftConfig.
#: - ``num``: ``fn`` values are the guarded dispatch sites (pair_probs,
#:   train_epoch, solve_xt — a handful, like ``xla``'s fn), ``output``
#:   the guarded output slot per site (probs|logits|loss|grid|residual),
#:   ``pair`` the parity path-pairs
#:   (fused_vs_materialized|incremental_vs_replay), ``quant`` the served
#:   side's table-storage mode on parity observations (bf16|int8,
#:   ``ops/quant.py::QUANTIZE_MODES``; f32 serving stays unlabeled so
#:   pre-quantization series addresses are stable) — the parity
#:   histograms split per mode are the in-production quantization error
#:   band.
#: - ``bench``: ``quant``/``kernel`` label the vaep_fused_quant sweep's
#:   summary gauges per (storage mode, first-layer lowering) — both
#:   bounded by code (QUANTIZE_MODES × pallas|xla).
#: - ``perf``: ``fn`` values are the instrumented dispatch loops (the
#:   ``instrument_jit`` names — pair_probs, train_epoch, solve_xt* — so
#:   the roofline and the compile observatory share books), ``bucket``
#:   the bounded shape dimension (serve ladder rung / pow-2 xT fleet
#:   size — bounded by construction, like ``serve``'s bucket).
#: - ``mem``: ``owner`` values are the residency ledger's registered
#:   subsystem names (registry, pipeline_feed, xt_fleet) plus the
#:   reserved ``unattributed`` remainder — a subsystem name by
#:   contract (``obs/residency.py::_OWNER_RE``), never an id.
#: - ``resil``: ``point`` values are the named fault points (a literal
#:   per marker — serve.dispatch, ingest.read, registry.load,
#:   batcher.flush, learn.publish), ``kind`` error|latency, ``site``
#:   the retry call sites (one literal per adoption — ingest.read,
#:   registry.load, recorder.dump, bench.ledger), ``outcome``
#:   retried|recovered|exhausted|permanent for retries and the
#:   breaker-probe / recovery verdicts elsewhere — all bounded by code.
#: - ``scenario``: ``n_perturbations_bucket`` is a grid's perturbation
#:   count and MUST be bucketed to powers of two
#:   (``scenario.engine.bucket_perturbations`` — the same ladder law as
#:   ``xt``'s ``n_grids``): an arbitrary ``P`` would mint a series per
#:   distinct grid size. ``verb`` is the bounded entry-point set
#:   (batch|looped|reference|serve).
#: - ``fleet``: ``replica`` values MUST come from the bounded
#:   ``obs/wire.py::ReplicaRegistry`` (validated id shape, hard budget,
#:   default 64 slots) — a replica id is a stable process-slot *name*
#:   (``replica-0``), never a free-form string (a pod hash, a
#:   timestamp); ``encode_snapshot``/``merge_wires``/``FleetAggregator``
#:   all refuse unregistered or malformed ids, so the gauge-merge's
#:   ``replica`` label and every ``fleet/*`` series stay bounded by the
#:   same contract. ``state`` is ok|stale, ``outcome`` ok|error (scrape
#:   verdicts), ``signal`` the divergence signal set
#:   (``obs/fleet.py::DIVERGENCE_SIGNALS``) — all bounded by code.
KNOWN_LABELS = {
    'bench': {'path', 'platform', 'quant', 'kernel'},
    'drift': {'feature'},
    'fleet': {'replica', 'state', 'outcome', 'signal'},
    'learn': {'source', 'stage', 'verdict', 'head', 'model'},
    'mem': {'span', 'device', 'owner'},
    'num': {'fn', 'output', 'pair', 'quant'},
    'perf': {'fn', 'bucket'},
    'pipeline': {'stage'},
    'resil': {'point', 'kind', 'site', 'outcome'},
    'scenario': {'verb', 'n_perturbations_bucket'},
    # seq: ``window`` values are the power-of-two window-length rungs
    # (``core.batch.window_ladder`` — O(log2(max_actions/128)) values by
    # construction, the time analogue of serve's ``bucket``).
    'seq': {'platform', 'window'},
    # serve: ``outcome`` is the AOT-tier load verdict (hit|stale|miss,
    # serve/aot_loads — serve/aot.py's three-valued contract).
    # ``replica`` values are lane ids minted through the same bounded
    # ``obs/wire.py::ReplicaRegistry`` contract as the fleet area
    # (RatingService registers ``r0..r{N-1}`` at construction): flush-
    # scoped serve metrics split per mesh replica lane, and the
    # single-replica service emits the unlabeled legacy series.
    'serve': {'reason', 'kind', 'bucket', 'segment', 'outcome', 'replica'},
    'slo': {'objective', 'outcome', 'window'},
    'train': {'path', 'platform'},
    'vaep': {'path', 'platform'},
    'xla': {'fn'},
    'xt': {'grid', 'solver', 'variant', 'backend', 'n_grids', 'overflow'},
}

#: methods through which a registered instrument takes label kwargs
LABEL_TAKING_METHODS = {'inc', 'observe', 'set', 'labels'}

#: implicit units of name-taking calls that never pass ``unit=``
DEFAULT_UNITS = {
    'timed': 's',
    'timed_labels': 's',
    'histogram': 's',
    'counter': 'count',
    'gauge': 'value',
}

DEFAULT_TARGETS = [
    'socceraction_tpu',
    'tools',
    'benchmarks',
    'examples',
    'docs/walkthrough',
    'bench.py',
    '__graft_entry__.py',
]


def iter_py_files(paths: List[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p) and p.endswith('.py'):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [
                    d for d in dirs if not d.startswith(('.', '__pycache__'))
                ]
                for f in sorted(files):
                    if f.endswith('.py'):
                        yield os.path.join(root, f)


def _call_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def collect_names(
    tree: ast.Module, path: str
) -> Iterator[Tuple[str, Optional[str], int, Optional[str]]]:
    """Yield ``(call, name, lineno, unit_literal_or_None)`` per name site.

    ``name`` is None for an f-string first argument (a dynamic-name
    site the cardinality rule rejects for metric calls). Span names
    carry no unit (``None`` sentinel distinct from a metric's implicit
    default) so a span and a metric may share an area prefix without
    tripping the unit-conflict rule.
    """
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        call = _call_name(node.func)
        if call not in NAME_TAKING_CALLS or not node.args:
            continue
        first = node.args[0]
        if isinstance(first, ast.JoinedStr):
            yield call, None, node.lineno, None
            continue
        if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
            continue
        unit: Optional[str] = DEFAULT_UNITS.get(call)
        for kw in node.keywords:
            if kw.arg == 'unit':
                if isinstance(kw.value, ast.Constant) and isinstance(
                    kw.value.value, str
                ):
                    unit = kw.value.value
                else:
                    unit = None  # dynamic unit: skip the conflict check
        yield call, first.value, node.lineno, unit


def collect_label_sites(tree: ast.Module) -> Iterator[Tuple[str, str, int]]:
    """Yield ``(metric_name, label_key, lineno)`` for every literal label.

    A literal label site is a ``.inc(...)`` / ``.observe(...)`` /
    ``.set(...)`` / ``.labels(...)`` call whose receiver is a
    ``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` call with a
    literal name, carrying explicit ``key=`` kwargs (``**labels`` dicts
    and instruments held in variables are out of static reach — the
    runtime cardinality guard covers those).
    """
    metric_calls = NAME_TAKING_CALLS - {'timed', 'timed_labels', 'span'}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (
            isinstance(func, ast.Attribute)
            and func.attr in LABEL_TAKING_METHODS
            and isinstance(func.value, ast.Call)
        ):
            continue
        recv = func.value
        if _call_name(recv.func) not in metric_calls or not recv.args:
            continue
        first = recv.args[0]
        if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
            continue
        for kw in node.keywords:
            # 'exemplar' is the observe() verb's reserved kwarg (trace
            # linkage), never a label dimension
            if kw.arg is not None and kw.arg != 'exemplar':
                yield first.value, kw.arg, node.lineno


def check_files(
    paths: List[str], areas: Optional[set] = None
) -> Tuple[List[str], int]:
    """(problems, n_sites) over every literal registration site.

    ``areas``, when given, is the allow-list of registered metric areas
    (:data:`KNOWN_AREAS`): a well-formed name whose leading segment is
    not in it is flagged. ``None`` (the default, and what the unit tests
    use on scratch files) checks the naming convention and unit
    conflicts only.
    """
    problems: List[str] = []
    units: Dict[str, Tuple[str, str]] = {}  # name -> (unit, first site)
    n_sites = 0
    for path in iter_py_files(paths):
        with open(path, encoding='utf-8') as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:  # the lint gate owns syntax errors
            problems.append(f'{path}:{e.lineno}: syntax error: {e.msg}')
            continue
        for call, name, lineno, unit in collect_names(tree, path):
            n_sites += 1
            site = f'{path}:{lineno}'
            if name is None:  # f-string first argument
                if call != 'span':
                    problems.append(
                        f"{site}: {call}(f'...') mints a metric name per "
                        'value — make the varying part a label on a fixed '
                        'area/stage name (Prometheus cardinality)'
                    )
                continue
            if not NAME_RE.match(name):
                problems.append(
                    f'{site}: {call}({name!r}) violates the area/stage '
                    "naming convention (lowercase segments joined by '/')"
                )
                continue  # the remaining rules presume a parseable name
            # every independent rule reports — a site violating several
            # surfaces ALL of them in one run, not one per fix-and-rerun
            if name.count('/') > 1:
                problems.append(
                    f'{site}: {call}({name!r}) nests deeper than '
                    'area/stage — a per-function (or per-anything) '
                    'dimension must be a label, not a name suffix'
                )
            if areas is not None and name.split('/')[0] not in areas:
                problems.append(
                    f'{site}: {call}({name!r}) uses unregistered area '
                    f'{name.split("/")[0]!r} (add it to KNOWN_AREAS to '
                    'register a new telemetry area)'
                )
            if unit is None:
                continue
            seen = units.get(name)
            if seen is None:
                units[name] = (unit, site)
            elif seen[0] != unit:
                problems.append(
                    f'{site}: {call}({name!r}) with unit={unit!r} conflicts '
                    f'with unit={seen[0]!r} at {seen[1]}'
                )
        for name, key, lineno in collect_label_sites(tree):
            area = name.split('/')[0]
            allowed = KNOWN_LABELS.get(area)
            if allowed is None:
                continue  # area without a label contract (yet)
            if key not in allowed:
                problems.append(
                    f'{path}:{lineno}: label {key!r} on {name!r} is not '
                    f'registered for area {area!r} (add it to KNOWN_LABELS '
                    'to govern the new dimension)'
                )
    return sorted(problems), n_sites


def main(argv: List[str]) -> int:
    targets = argv or DEFAULT_TARGETS
    problems, n_sites = check_files(targets, areas=KNOWN_AREAS)
    for p in problems:
        print(p)
    print(
        f'check_metric_names: {n_sites} literal name site(s), '
        f'{len(problems)} problem(s)'
    )
    return 1 if problems else 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
