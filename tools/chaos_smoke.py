"""End-to-end chaos smoke: one seeded fault schedule, replayed twice.

The ``make chaos-smoke`` gate for the resilience layer: fit a tiny VAEP
model on synthetic actions, then drive the SAME seeded
:class:`~socceraction_tpu.resil.faults.FaultPlan` through a live
:class:`~socceraction_tpu.serve.RatingService` twice and assert the
whole failure story — injection, supervision, degradation, recovery —
happened, identically, both times:

- a ``batcher.flush`` injection kills the flusher thread mid-load; the
  supervised restart replaces it, re-queues the taken request, and the
  caller's future still resolves (no stranded futures, no dropped work);
- two consecutive ``serve.dispatch`` injections trip the circuit
  breaker; the affected flushes and everything after them are served
  through the materialized reference fallback (correct values, degraded
  health), and after the recovery dwell one half-open probe flush closes
  the breaker again (health back to ``ok``);
- the plan's :attr:`~socceraction_tpu.resil.faults.FaultPlan.history`
  from run 2 is **bit-identical** to run 1 — the reproducibility
  contract chaos debugging depends on;
- ``obsctl resil`` over the closed run log round-trips the injected
  faults, breaker trips/probes and breaker state.

Exit 0 on success; any violated invariant is a non-zero exit with the
evidence printed. CPU-sized (a few seconds).
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

__all__ = ['main']

#: the seeded schedule: one flusher death, two dispatch failures
SEED = 7


def _plan():
    from socceraction_tpu.resil.faults import FaultPlan, FaultSpec

    return FaultPlan(
        seed=SEED,
        specs=[
            # the flusher's 2nd take dies mid-load -> supervised restart
            FaultSpec('batcher.flush', error=RuntimeError, nth=2),
            # dispatch calls 3 and 4 fail -> breaker (threshold 2) trips
            FaultSpec('serve.dispatch', error=RuntimeError, on_calls=(3, 4)),
        ],
    )


def _drive(model, frame, runlog_path=None):
    """One seeded chaos run; returns (history, evidence dict)."""
    import contextlib as _ctx

    from socceraction_tpu.obs import RunLog
    from socceraction_tpu.resil.breaker import CircuitBreaker
    from socceraction_tpu.serve import RatingService

    plan = _plan()
    # the breaker runs on an injected fake clock so the schedule is
    # deterministic regardless of host speed: wall-clock dwells would
    # let a slow run's later flushes drift past the recovery window and
    # probe-close the breaker before the mid-schedule health check
    clock = {'t': 0.0}
    breaker = CircuitBreaker(
        failure_threshold=2,
        recovery_time_s=1000.0,
        name='serve.dispatch',
        clock=lambda: clock['t'],
    )
    log_cm = (
        RunLog(runlog_path, config={'smoke': 'chaos', 'seed': SEED})
        if runlog_path
        else _ctx.nullcontext()
    )
    with log_cm:
        with RatingService(
            model,
            max_actions=256,
            max_batch_size=1,
            max_wait_ms=1.0,
            breaker=breaker,
        ) as service:
            with plan:
                ratings = []
                for _ in range(6):
                    fut = service.rate(frame, home_team_id=100)
                    ratings.append(fut.result(timeout=120))
                health_degraded = service.health()
                # advance the fake clock past the recovery dwell: the
                # next flush is the half-open probe; the fused path is
                # healthy again (the injection budget is spent), so it
                # closes the breaker
                clock['t'] += 2000.0
                fut = service.rate(frame, home_team_id=100)
                ratings.append(fut.result(timeout=120))
                health_recovered = service.health()
            evidence = {
                'ratings_ok': all(len(r) == len(frame) for r in ratings),
                'n_requests': len(ratings),
                'flusher_restarts': service.health()['flusher_restarts'],
                'breaker_trips': service.breaker.trips,
                'status_degraded': health_degraded['status'],
                'breaker_state_degraded': health_degraded['breaker']['state'],
                'status_recovered': health_recovered['status'],
                'breaker_state_recovered': health_recovered['breaker'][
                    'state'
                ],
            }
    return plan.history, evidence


def main() -> int:
    """Drive the seeded chaos schedule twice; returns an exit code."""
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import tempfile

    import numpy as np
    import pandas as pd

    from socceraction_tpu.core.synthetic import synthetic_actions_frame
    from socceraction_tpu.vaep.base import VAEP
    from tools.obsctl import main as obsctl_main

    frame = synthetic_actions_frame(game_id=0, seed=0, n_actions=120)
    model = VAEP()
    game = pd.Series({'game_id': 0, 'home_team_id': 100})
    np.random.seed(0)
    model.fit(
        model.compute_features(game, frame),
        model.compute_labels(game, frame),
        learner='mlp',
        tree_params={'hidden': (8,), 'max_epochs': 2},
    )

    problems = []
    with tempfile.TemporaryDirectory(prefix='chaos-smoke-') as tmp:
        runlog_path = os.path.join(tmp, 'obs.jsonl')
        history1, ev = _drive(model, frame, runlog_path)
        history2, _ = _drive(model, frame)

        # -- the failure story happened ---------------------------------
        if not ev['ratings_ok']:
            problems.append('a rating came back misaligned with its request')
        if ev['flusher_restarts'] != 1:
            problems.append(
                f'expected exactly 1 supervised flusher restart, saw '
                f'{ev["flusher_restarts"]}'
            )
        if ev['breaker_trips'] != 1:
            problems.append(
                f'expected exactly 1 breaker trip, saw {ev["breaker_trips"]}'
            )
        if (ev['status_degraded'], ev['breaker_state_degraded']) != (
            'degraded',
            'open',
        ):
            problems.append(
                'mid-schedule health should be degraded/open, saw '
                f'{ev["status_degraded"]}/{ev["breaker_state_degraded"]}'
            )
        if (ev['status_recovered'], ev['breaker_state_recovered']) != (
            'ok',
            'closed',
        ):
            problems.append(
                'post-recovery health should be ok/closed, saw '
                f'{ev["status_recovered"]}/{ev["breaker_state_recovered"]}'
            )

        # -- and it happened identically both times ----------------------
        if history1 != history2:
            problems.append(
                f'seed {SEED} is not reproducible:\n'
                f'  run 1: {json.dumps(history1, sort_keys=True)}\n'
                f'  run 2: {json.dumps(history2, sort_keys=True)}'
            )
        fired = [(h['point'], h['kind']) for h in history1]
        expected = [
            ('batcher.flush', 'error'),
            ('serve.dispatch', 'error'),
            ('serve.dispatch', 'error'),
        ]
        if fired != expected:
            problems.append(
                f'injection sequence {fired} != expected {expected}'
            )

        # -- and obsctl resil reconstructs it from the run log -----------
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = obsctl_main(['resil', runlog_path, '--json'])
        if rc != 0:
            problems.append('obsctl resil failed on the run log')
        else:
            summary = json.loads(out.getvalue())
            faults = {
                (row['point'], row['kind']): row['total']
                for row in summary.get('faults_injected', [])
            }
            if faults.get(('batcher.flush', 'error'), 0) < 1:
                problems.append(
                    f'obsctl resil lost the flusher injection: {faults}'
                )
            if faults.get(('serve.dispatch', 'error'), 0) < 2:
                problems.append(
                    f'obsctl resil lost the dispatch injections: {faults}'
                )
            breaker = summary.get('breaker') or {}
            if not breaker.get('trips'):
                problems.append(f'obsctl resil lost the breaker trip: {breaker}')
            if breaker.get('state') != 'closed':
                problems.append(
                    f'final breaker state in the log should be closed: '
                    f'{breaker}'
                )
            kinds = {
                e.get('event') or e.get('kind')
                for e in summary.get('events', [])
            }
            missing = {'fault_injected', 'breaker_transition'} - kinds
            if missing:
                problems.append(f'run log missing resil events: {missing}')

    if problems:
        for p in problems:
            print(f'chaos-smoke: FAIL - {p}')
        return 1
    print(
        f'chaos-smoke: OK - seed {SEED} reproduced '
        f'{len(history1)} injection(s) bit-for-bit; flusher restart '
        'absorbed, breaker tripped -> half-open probe -> closed, '
        'health ok'
    )
    return 0


if __name__ == '__main__':
    sys.exit(main())
