"""Dependency-free API reference generator.

The reference ships a 26-page Sphinx API reference built by autodoc
(``/root/reference/docs/api/*.rst``); this image has no sphinx, so the
same surface is generated from the AST instead (the ``tools/lint.py``
pattern): one markdown page per public module under ``docs/api/``, every
public class/function with its real signature and docstring. Output is
deterministic — byte-stable across runs — so the committed pages are
drift-checked by ``tests/test_api_docs.py`` exactly like the walkthrough
outputs: regenerating must reproduce the tree, and a changed public
surface fails the suite until the docs are regenerated.

Usage::

    python tools/docgen.py [--check] [--out docs/api]

``--check`` writes nothing and exits 1 when the committed pages differ
from what would be generated (the drift gate).
"""

from __future__ import annotations

import argparse
import ast
import os
import sys
from typing import Dict, Iterator, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = 'socceraction_tpu'

#: operator-facing tool modules documented alongside the package (the
#: rest of tools/ is build machinery, not API surface)
EXTRA_MODULES = (
    ('tools.obsctl', os.path.join('tools', 'obsctl.py')),
    ('tools.benchdiff', os.path.join('tools', 'benchdiff.py')),
)


def iter_modules(root: str) -> Iterator[Tuple[str, str]]:
    """Yield ``(dotted_name, path)`` for every public module, sorted.

    Package modules first, then the :data:`EXTRA_MODULES` tool pages.
    """
    yield from _iter_package_modules(root)
    for dotted, rel in EXTRA_MODULES:
        path = os.path.join(root, rel)
        if os.path.isfile(path):  # absent in stub trees (the gate tests)
            yield dotted, path


def _iter_package_modules(root: str) -> Iterator[Tuple[str, str]]:
    out = []
    for dirpath, dirnames, filenames in os.walk(os.path.join(root, PACKAGE)):
        dirnames[:] = sorted(d for d in dirnames if not d.startswith('_') and d != '__pycache__')
        for fn in sorted(filenames):
            if not fn.endswith('.py'):
                continue
            stem = fn[:-3]
            if stem.startswith('_') and stem != '__init__':
                continue
            rel = os.path.relpath(os.path.join(dirpath, fn), root)
            dotted = rel[:-3].replace(os.sep, '.')
            if dotted.endswith('.__init__'):
                dotted = dotted[: -len('.__init__')]
            out.append((dotted, os.path.join(dirpath, fn)))
    return iter(sorted(out))


def _signature(node: ast.AST) -> str:
    """Render a def's signature from the AST (annotations + defaults)."""
    a = node.args
    parts: List[str] = []

    def fmt(arg: ast.arg, default: Optional[ast.expr]) -> str:
        s = arg.arg
        if arg.annotation is not None:
            s += ': ' + ast.unparse(arg.annotation)
        if default is not None:
            s += ' = ' + ast.unparse(default) if arg.annotation else '=' + ast.unparse(default)
        return s

    pos = a.posonlyargs + a.args
    defaults: List[Optional[ast.expr]] = [None] * (len(pos) - len(a.defaults)) + list(a.defaults)
    for i, (arg, d) in enumerate(zip(pos, defaults)):
        parts.append(fmt(arg, d))
        if a.posonlyargs and i == len(a.posonlyargs) - 1:
            parts.append('/')
    if a.vararg is not None:
        parts.append('*' + a.vararg.arg)
    elif a.kwonlyargs:
        parts.append('*')
    for arg, d in zip(a.kwonlyargs, a.kw_defaults):
        parts.append(fmt(arg, d))
    if a.kwarg is not None:
        parts.append('**' + a.kwarg.arg)
    sig = '(' + ', '.join(parts) + ')'
    if node.returns is not None:
        sig += ' -> ' + ast.unparse(node.returns)
    return sig


def _module_all(tree: ast.Module) -> Optional[List[str]]:
    """The module's declared export list, if statically resolvable.

    Handles plain assignment, annotated assignment and ``__all__ += [...]``
    extension; a non-literal value falls back to the underscore rule.
    """
    names: Optional[List[str]] = None
    for node in tree.body:
        target = value = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            target, value = node.target, node.value
        if not (isinstance(target, ast.Name) and target.id == '__all__'):
            continue
        if value is None:
            continue
        try:
            literal = list(ast.literal_eval(value))
        except Exception:
            return None
        if isinstance(node, ast.AugAssign):
            names = (names or []) + literal
        else:
            names = literal
    return names


def _walk_public(body: List[ast.stmt]) -> Iterator[ast.stmt]:
    """Top-level statements incl. those under optional-dependency gates."""
    for node in body:
        if isinstance(node, (ast.If, ast.Try)):
            sub: List[List[ast.stmt]] = [node.body, node.orelse]
            if isinstance(node, ast.Try):
                sub += [h.body for h in node.handlers] + [node.finalbody]
            for b in sub:
                yield from _walk_public(b)
        else:
            yield node


def _first_line(doc: Optional[str]) -> str:
    if not doc:
        return ''
    return doc.strip().splitlines()[0].strip()


def _doc_block(doc: Optional[str]) -> List[str]:
    """Render a docstring as markdown lines."""
    if not doc:
        return ['*Undocumented.*', '']
    lines = [ln.rstrip() for ln in doc.strip().splitlines()]
    return lines + ['']


class ModuleDoc:
    """Extracted public surface of one module."""

    def __init__(self, dotted: str, path: str) -> None:
        self.dotted = dotted
        with open(path, encoding='utf-8') as fh:
            self.tree = ast.parse(fh.read())
        self.doc = ast.get_docstring(self.tree)
        self.exported = _module_all(self.tree)
        self.functions: List[ast.stmt] = []
        self.classes: List[ast.ClassDef] = []
        self.constants: List[str] = []
        for node in _walk_public(self.tree.body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if self._is_public(node.name):
                    self.functions.append(node)
            elif isinstance(node, ast.ClassDef):
                if self._is_public(node.name):
                    self.classes.append(node)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    if isinstance(t, ast.Name) and self._is_public(t.id) and t.id != '__all__':
                        self.constants.append(t.id)

    def _is_public(self, name: str) -> bool:
        if self.exported is not None:
            return name in self.exported
        return not name.startswith('_')

    def undocumented(self) -> List[str]:
        """Public defs/classes without a docstring (drift-gated to zero)."""
        missing = []
        if not self.doc:
            missing.append(self.dotted)
        for fn in self.functions:
            if not ast.get_docstring(fn):
                missing.append(f'{self.dotted}.{fn.name}')
        for cls in self.classes:
            if not ast.get_docstring(cls):
                missing.append(f'{self.dotted}.{cls.name}')
            for node in cls.body:
                if (
                    isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and not node.name.startswith('_')
                    and not ast.get_docstring(node)
                ):
                    missing.append(f'{self.dotted}.{cls.name}.{node.name}')
        return missing

    def render(self) -> str:
        out: List[str] = [f'# `{self.dotted}`', '']
        out += _doc_block(self.doc)
        if self.constants:
            out += ['## Constants', '']
            for name in self.constants:
                out.append(f'- `{name}`')
            out.append('')
        for cls in self.classes:
            bases = ', '.join(ast.unparse(b) for b in cls.bases)
            suffix = f'({bases})' if bases else ''
            out += [f'## class `{cls.name}{suffix}`', '']
            out += _doc_block(ast.get_docstring(cls))
            for node in cls.body:
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if node.name.startswith('_') and node.name != '__init__':
                    continue
                out += [f'### `{cls.name}.{node.name}{_signature(node)}`', '']
                out += _doc_block(ast.get_docstring(node))
        for fn in self.functions:
            out += [f'## `{fn.name}{_signature(fn)}`', '']
            out += _doc_block(ast.get_docstring(fn))
        return '\n'.join(out).rstrip() + '\n'


def generate(root: str) -> Dict[str, str]:
    """Return ``{relative_page_path: content}`` for the whole package."""
    pages: Dict[str, str] = {}
    index: List[str] = [
        '# API reference',
        '',
        'Generated by `tools/docgen.py` from the package AST and docstrings;',
        'regenerate with `make docs`. One page per public module. Parity',
        'columns and reference `file:line` citations live in the docstrings',
        'themselves; `docs/api.md` is the hand-written layer map.',
        '',
        '| Module | Summary |',
        '|---|---|',
    ]
    missing_all: List[str] = []
    for dotted, path in iter_modules(root):
        mod = ModuleDoc(dotted, path)
        page = dotted + '.md'
        pages[page] = mod.render()
        index.append(f'| [`{dotted}`]({page}) | {_first_line(mod.doc)} |')
        missing_all += mod.undocumented()
    index.append('')
    pages['index.md'] = '\n'.join(index)
    if missing_all:
        raise SystemExit(
            'undocumented public symbols (add docstrings):\n  '
            + '\n  '.join(missing_all)
        )
    return pages


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default=os.path.join(REPO, 'docs', 'api'))
    ap.add_argument('--check', action='store_true', help='verify, write nothing')
    args = ap.parse_args(argv)
    pages = generate(REPO)
    if args.check:
        stale = []
        for rel, content in pages.items():
            path = os.path.join(args.out, rel)
            try:
                with open(path, encoding='utf-8') as fh:
                    if fh.read() != content:
                        stale.append(rel)
            except FileNotFoundError:
                stale.append(rel)
        extra = [
            fn
            for fn in (os.listdir(args.out) if os.path.isdir(args.out) else [])
            if fn.endswith('.md') and fn not in pages
        ]
        if stale or extra:
            print('API docs drift: regenerate with `make docs`')
            for rel in stale:
                print(f'  stale/missing: {rel}')
            for rel in extra:
                print(f'  orphaned: {rel}')
            return 1
        print(f'docs/api up to date ({len(pages)} pages)')
        return 0
    os.makedirs(args.out, exist_ok=True)
    for rel, content in pages.items():
        with open(os.path.join(args.out, rel), 'w', encoding='utf-8') as fh:
            fh.write(content)
    print(f'wrote {len(pages)} pages to {args.out}')
    return 0


if __name__ == '__main__':
    raise SystemExit(main(sys.argv[1:]))
