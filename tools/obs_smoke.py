"""End-to-end request-tracing + numerics smoke: one guarded, probed request.

The ``make obs-smoke`` gate for the request-observability AND
numerics-observability layers: fit a tiny VAEP model on synthetic
actions, serve ONE rating request through a :class:`RatingService`
under a :class:`RunLog` — with the in-dispatch finite guards enabled
(the default) and a sample-everything
:class:`~socceraction_tpu.obs.parity.ParityProbe` attached — then
reconstruct the request with ``obsctl trace`` and the numeric-health
surface with ``obsctl numerics`` and assert every piece is there:

- the future carries its ``request_id`` / ``RequestContext``;
- ``request_enqueue`` and ``request_done`` events landed in the log;
- the ``serve/flush`` span lists the id among its coalesced children;
- the segment decomposition covers queue_wait / pad / dispatch / slice
  and sums to (at most) the request's wall;
- the SLO engine scored the request and reports full budget remaining;
- the guarded dispatch detected zero non-finite values and ``health()``
  reports a clean numerics block;
- the parity probe re-rated the flush through the materialized
  reference within 1e-5 max abs error, and ``obsctl numerics`` over the
  closed run log round-trips the probe's statistics.

Exit 0 on success; any assertion failure is a non-zero exit with the
reconstructed trace printed for debugging. CPU-sized (a few seconds).
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

__all__ = ['main']


def main() -> int:
    """Drive one traced request end to end; returns a process exit code."""
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import numpy as np
    import pandas as pd

    from socceraction_tpu.core.synthetic import synthetic_actions_frame
    from socceraction_tpu.obs import RunLog, SLOConfig
    from socceraction_tpu.obs.parity import ParityProbe
    from socceraction_tpu.serve import RatingService
    from socceraction_tpu.vaep.base import VAEP
    from tools.obsctl import main as obsctl_main

    frame = synthetic_actions_frame(game_id=0, seed=0, n_actions=120)
    model = VAEP()
    game = pd.Series({'game_id': 0, 'home_team_id': 100})
    np.random.seed(0)
    model.fit(
        model.compute_features(game, frame),
        model.compute_labels(game, frame),
        learner='mlp',
        tree_params={'hidden': (8,), 'max_epochs': 2},
    )

    with tempfile.TemporaryDirectory(prefix='obs-smoke-') as tmp:
        runlog_path = os.path.join(tmp, 'obs.jsonl')
        probe = ParityProbe(sample_rate=1.0, max_abs_err=1e-4)
        with RunLog(runlog_path, config={'smoke': 'obs'}):
            with RatingService(
                model,
                max_actions=256,
                max_batch_size=4,
                max_wait_ms=1.0,
                slo=SLOConfig.simple(latency_ms=60_000.0),
                parity=probe,
            ) as service:
                future = service.rate(frame, home_team_id=100)
                ratings = future.result(timeout=120)
                request_id = future.request_id
                probe.flush(timeout=120)
                health = service.health()
        assert len(ratings) == len(frame), 'ratings misaligned with request'
        assert request_id, 'future carries no request id'
        assert future.context.segments, 'context carries no segments'

        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = obsctl_main(['trace', request_id, runlog_path, '--json'])
        if rc != 0:
            print(out.getvalue())
            print('obs-smoke: FAIL - obsctl trace could not reconstruct')
            return 1
        trace = json.loads(out.getvalue())

        problems = []
        if trace.get('status') != 'ok':
            problems.append(f'status {trace.get("status")!r} != ok')
        if trace.get('enqueue') is None:
            problems.append('no request_enqueue event')
        if trace.get('done') is None:
            problems.append('no request_done event')
        flush = trace.get('flush')
        if flush is None:
            problems.append('no serve/flush span lists this request')
        elif request_id not in (flush.get('attrs') or {}).get(
            'request_ids', ()
        ):
            problems.append('flush span does not link the request id')
        segments = trace.get('segments') or {}
        missing = {'queue_wait', 'pad', 'dispatch', 'slice'} - set(segments)
        if missing:
            problems.append(f'segments missing {sorted(missing)}')
        wall = trace.get('wall_s') or 0.0
        if segments and sum(segments.values()) > wall * 1.05 + 1e-3:
            problems.append(
                f'segments sum {sum(segments.values()):.4f}s exceeds '
                f'wall {wall:.4f}s'
            )
        slo = health.get('slo', {}).get('objectives', {})
        if not slo:
            problems.append('health() reports no SLO objectives')
        elif any(
            o.get('budget_remaining') not in (None, 1.0)
            for o in slo.values()
        ):
            problems.append(f'unexpected budget burn in {slo}')

        # the numerics half: the guarded dispatch was clean, the parity
        # probe ran within band, and obsctl numerics round-trips it all
        numerics = health.get('numerics') or {}
        if numerics.get('ok') is not True:
            problems.append(f'health() numerics degraded: {numerics}')
        if numerics.get('nonfinite_events'):
            problems.append(
                f'{numerics["nonfinite_events"]} nonfinite event(s) on a '
                'clean request'
            )
        pstats = probe.stats()
        if pstats['probes'] < 1:
            problems.append('parity probe never sampled the flush')
        elif pstats['max_abs_err'] is None or pstats['max_abs_err'] > 1e-5:
            problems.append(
                f'parity vs reference {pstats["max_abs_err"]} > 1e-5'
            )
        if pstats['exceedances']:
            problems.append(f'parity exceedances: {pstats["exceedances"]}')

        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = obsctl_main(['numerics', runlog_path, '--json'])
        if rc != 0:
            problems.append('obsctl numerics failed on the run log')
            num_summary = {}
        else:
            num_summary = json.loads(out.getvalue())
            pairs = {
                row.get('pair'): row
                for row in num_summary.get('parity', [])
            }
            fused = pairs.get('fused_vs_materialized')
            if fused is None:
                problems.append(
                    'obsctl numerics lost the fused_vs_materialized probe'
                )
            elif not fused.get('probes'):
                problems.append(f'numerics round-trip has no probes: {fused}')
            if any(row['total'] for row in num_summary.get('nonfinite', [])):
                problems.append(
                    f'nonfinite totals on a clean run: {num_summary}'
                )

        if problems:
            print(json.dumps(trace, indent=1, sort_keys=True, default=str))
            for p in problems:
                print(f'obs-smoke: FAIL - {p}')
            return 1

        seg_ms = {k: round(v * 1e3, 3) for k, v in segments.items()}
        print(
            f'obs-smoke: OK - request {request_id} reconstructed '
            f'(wall {wall * 1e3:.2f}ms, segments {seg_ms}, '
            f'{len(slo)} SLO objective(s) at full budget; numerics clean, '
            f'parity {pstats["probes"]} probe(s) max abs err '
            f'{pstats["max_abs_err"]:.2e})'
        )
    return 0


if __name__ == '__main__':
    sys.exit(main())
