"""Two-artifact bench regression verdicts over the ledger's headline rates.

``bench.py`` appends every artifact it emits to the append-only
``bench_history/ledger.jsonl`` (one JSON object per line, newest last —
the repo's measured performance trajectory). This tool turns any two
artifacts into a regression verdict::

    python tools/benchdiff.py                      # last two ledger entries
    python tools/benchdiff.py old.json new.json    # two artifact files
    python tools/benchdiff.py --json               # machine-readable
    python tools/benchdiff.py --threshold 0.05     # tighter band

An artifact argument may be a JSON object file (one ``bench.py`` output)
or a JSONL ledger (the newest entry is used; with a single ledger
argument the newest entry is compared against the most recent PREVIOUS
entry with the same metric+platform — ``make bench-smoke`` interleaves
several metrics in one ledger, and "the last two lines" would pair a
serve sweep with an xT sweep). The verdicts cover the
headline rate keys both artifacts carry (``value`` — the artifact's own
headline metric — plus the per-path rates like
``fused_actions_per_sec``): ``regression`` when the new rate dropped
more than ``--threshold`` (default 10%) below the old, ``improvement``
when it rose past the same band, ``ok`` between. Artifacts measured on
different platforms or with different headline metrics are refused as
``incomparable`` (comparing a TPU run against its CPU fallback would
manufacture a regression). Cold-start artifacts additionally diff
**per phase** (import / registry_load / device_upload /
aot_deserialize / ladder_compile / first_dispatch): the wall verdict
gates, the phase verdicts name which startup phase moved.

Exit codes: 0 all ok/improved, 1 at least one regression, 2 unusable
input. Wired as ``make bench-diff``; dependency-free (stdlib only).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

__all__ = ['HEADLINE_KEYS', 'compare_artifacts', 'main']

#: Rate keys compared when present in BOTH artifacts (higher-is-better
#: unless the artifact's metric says otherwise — see LOWER_IS_BETTER).
HEADLINE_KEYS: Tuple[str, ...] = (
    'value',
    'fused_actions_per_sec',
    'materialized_actions_per_sec',
    'fused_bf16_actions_per_sec',
    'peak_requests_per_sec',
    'peak_actions_per_sec',
    # the mesh-replicated serving sweep's headline: sustained req/s at 4
    # replicas (bench.py --mesh-sweep; its `value` duplicates this key)
    'serve_req_per_sec_r4',
    # the capacity observatory's serve headline: AOT cost FLOPs over the
    # measured flush wall (bench.py serve_throughput embeds it)
    'serve_achieved_flops_per_sec',
    # the counterfactual engine's headline: valued counterfactuals per
    # second in one folded dispatch (bench.py --cf-smoke; its `value`
    # duplicates this key)
    'cf_values_per_sec',
    # the sequence head's serving headline: actions rated through the
    # window-rung ladder per second (bench.py --seq-smoke; its `value`
    # duplicates this key)
    'seq_actions_per_sec',
)

#: Artifact metrics whose headline ``value`` is a WALL or a SIZE, not a
#: rate — a rise is the regression (``bench.py --cold-start``'s
#: process-start → first-rated-action seconds; the quantized fold's HBM
#: table bytes, where growth means fewer model versions fit warm). Only
#: ``value`` flips direction: the other HEADLINE_KEYS stay rates
#: wherever they appear.
LOWER_IS_BETTER: Tuple[str, ...] = (
    'cold_start_seconds',
    'cold_start_cache_hit_seconds',
    'cold_start_aot_seconds',
    'vaep_quant_table_bytes',
    # the fleet telemetry plane's own overhead (bench.py --fleet-smoke:
    # scrape + merge wall at the top replica count) — the front end
    # pays these on the serving box, so growth is the regression
    'fleet_scrape_seconds',
    'fleet_merge_seconds',
)

#: Wall-breakdown metrics (the cold-start family): when BOTH artifacts
#: carry a ``phase_seconds`` dict, each shared phase gets its own
#: lower-is-better verdict — a cold-start regression then NAMES the
#: phase that moved (import? ladder_compile? aot_deserialize?) instead
#: of reporting an opaque wall. Phase verdicts use a floor
#: (PHASE_MIN_SECONDS) so a 0.01s→0.02s jitter on a near-zero phase
#: cannot page anyone, and they never count toward the exit-code
#: regression tally on their own when the wall stayed inside the band —
#: they are the diagnosis, the wall is the gate.
PHASE_BREAKDOWN_METRICS: Tuple[str, ...] = (
    'cold_start_seconds',
    'cold_start_cache_hit_seconds',
    'cold_start_aot_seconds',
)

#: phases below this wall (in BOTH artifacts) are skipped in the
#: per-phase diff: ratios over hundredths of a second are noise
PHASE_MIN_SECONDS = 0.1


def default_ledger() -> str:
    """The repo ledger path (``SOCCERACTION_TPU_BENCH_HISTORY`` override)."""
    hist = os.environ.get(
        'SOCCERACTION_TPU_BENCH_HISTORY', os.path.join(REPO, 'bench_history')
    )
    return os.path.join(hist, 'ledger.jsonl')


def _read_entries(path: str) -> List[Dict[str, Any]]:
    """Artifacts from ``path``: a JSON object file or a JSONL ledger."""
    with open(path, encoding='utf-8') as fh:
        text = fh.read()
    stripped = text.strip()
    if not stripped:
        return []
    try:
        obj = json.loads(stripped)
        if isinstance(obj, dict):
            return [obj]
    except json.JSONDecodeError:
        pass
    entries = []
    for lineno, line in enumerate(stripped.splitlines(), start=1):
        line = line.strip()
        if not line:
            continue
        try:
            entry = json.loads(line)
        except json.JSONDecodeError:
            # a torn tail line (bench killed mid-append) is expected and
            # must not fail the whole ledger parse — but say so: a torn
            # line ANYWHERE else suggests real corruption worth a look
            print(
                f'benchdiff: warning: skipping corrupt ledger line '
                f'{lineno} in {path} (torn append?)',
                file=sys.stderr,
            )
            continue
        if isinstance(entry, dict):
            entries.append(entry)
    return entries


def _label(entry: Dict[str, Any]) -> str:
    ts = entry.get('recorded_unix')
    metric = entry.get('metric', '?')
    platform = entry.get('platform', '?')
    stamp = f'@{ts:.0f}' if isinstance(ts, (int, float)) else ''
    return f'{metric}[{platform}]{stamp}'


def compare_artifacts(
    old: Dict[str, Any], new: Dict[str, Any], threshold: float = 0.10
) -> Dict[str, Any]:
    """Per-rate verdicts between two artifacts (see module docstring)."""
    result: Dict[str, Any] = {
        'old': _label(old),
        'new': _label(new),
        'threshold': threshold,
        'verdicts': [],
        'regressions': 0,
        'improvements': 0,
    }
    if old.get('metric') != new.get('metric') or old.get('platform') != new.get(
        'platform'
    ):
        result['incomparable'] = (
            f'artifacts measure different things: {_label(old)} vs '
            f'{_label(new)} — regression math across metrics/platforms '
            'is meaningless'
        )
        return result
    for key in HEADLINE_KEYS:
        a, b = old.get(key), new.get(key)
        if not isinstance(a, (int, float)) or not isinstance(b, (int, float)):
            continue
        if a <= 0:
            continue  # a degraded/zero baseline cannot anchor a ratio
        lower_better = key == 'value' and new.get('metric') in LOWER_IS_BETTER
        ratio = b / a
        if ratio < 1.0 - threshold:
            verdict = 'improvement' if lower_better else 'regression'
        elif ratio > 1.0 + threshold:
            verdict = 'regression' if lower_better else 'improvement'
        else:
            verdict = 'ok'
        if verdict == 'regression':
            result['regressions'] += 1
        elif verdict == 'improvement':
            result['improvements'] += 1
        name = new.get('metric', key) if key == 'value' else key
        result['verdicts'].append(
            {
                'rate': name,
                'old': a,
                'new': b,
                'ratio': round(ratio, 4),
                'direction': 'lower_is_better' if lower_better else 'higher_is_better',
                'verdict': verdict,
            }
        )
    result['phases'] = _phase_verdicts(old, new, threshold)
    return result


def _phase_verdicts(
    old: Dict[str, Any], new: Dict[str, Any], threshold: float
) -> List[Dict[str, Any]]:
    """Per-phase wall verdicts for the cold-start family (see
    PHASE_BREAKDOWN_METRICS): the diagnosis layer under the wall
    verdict, naming WHICH startup phase moved."""
    if new.get('metric') not in PHASE_BREAKDOWN_METRICS:
        return []
    old_phases = old.get('phase_seconds')
    new_phases = new.get('phase_seconds')
    if not isinstance(old_phases, dict) or not isinstance(new_phases, dict):
        return []
    verdicts: List[Dict[str, Any]] = []
    for phase in sorted(set(old_phases) & set(new_phases)):
        a, b = old_phases.get(phase), new_phases.get(phase)
        if not isinstance(a, (int, float)) or not isinstance(b, (int, float)):
            continue
        if max(a, b) < PHASE_MIN_SECONDS:
            continue  # sub-jitter phase: a ratio here is noise
        if a <= 0:
            # a phase that appeared from ~0 (aot_deserialize landing, a
            # new compile step) has no ratio; report it without a verdict
            verdicts.append(
                {'phase': phase, 'old': a, 'new': b, 'verdict': 'appeared'}
            )
            continue
        ratio = b / a
        if ratio > 1.0 + threshold:
            verdict = 'regression'
        elif ratio < 1.0 - threshold:
            verdict = 'improvement'
        else:
            verdict = 'ok'
        verdicts.append(
            {
                'phase': phase,
                'old': a,
                'new': b,
                'ratio': round(ratio, 4),
                'verdict': verdict,
            }
        )
    return verdicts


def _render(result: Dict[str, Any]) -> None:
    if 'incomparable' in result:
        print(f'benchdiff: INCOMPARABLE - {result["incomparable"]}')
        return
    print(f'benchdiff: {result["old"]}  ->  {result["new"]}')
    for v in result['verdicts']:
        print(
            f'  {v["verdict"].upper().ljust(11)} {v["rate"]}: '
            f'{v["old"]:g} -> {v["new"]:g} (x{v["ratio"]:.3f})'
        )
    for p in result.get('phases', []):
        line = (
            f'    phase {p["verdict"].upper().ljust(11)} {p["phase"]}: '
            f'{p["old"]:g}s -> {p["new"]:g}s'
        )
        if 'ratio' in p:
            line += f' (x{p["ratio"]:.3f})'
        print(line)
    print(
        f'benchdiff: {len(result["verdicts"])} rate(s), '
        f'{result["regressions"]} regression(s), '
        f'{result["improvements"]} improvement(s) '
        f'(threshold {result["threshold"]:.0%})'
    )


def main(argv: Optional[List[str]] = None) -> int:
    """Parse arguments, compare, print verdicts; returns the exit code."""
    parser = argparse.ArgumentParser(
        prog='benchdiff', description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        'artifacts', nargs='*',
        help='0 args: last two ledger entries; 1 ledger: its last two; '
        '2 args: old then new (JSON artifact or JSONL ledger each)',
    )
    parser.add_argument('--threshold', type=float, default=0.10)
    parser.add_argument('--json', action='store_true')
    args = parser.parse_args(argv)

    paths = args.artifacts or [default_ledger()]
    try:
        if len(paths) == 1:
            entries = _read_entries(paths[0])
            if len(entries) < 2:
                print(
                    f'benchdiff: need two artifacts; {paths[0]!r} has '
                    f'{len(entries)} (run `make bench` or `make '
                    'bench-smoke` twice to grow the ledger)',
                    file=sys.stderr,
                )
                return 2
            new = entries[-1]
            # the most recent earlier run of the SAME measurement — a
            # ledger interleaves metrics (train/serve/xt smokes), and
            # pairing adjacent lines would compare different things
            old = next(
                (
                    e
                    for e in reversed(entries[:-1])
                    if e.get('metric') == new.get('metric')
                    and e.get('platform') == new.get('platform')
                ),
                None,
            )
            if old is None:
                print(
                    f'benchdiff: no earlier {new.get("metric")!r} '
                    f'[{new.get("platform")}] entry in {paths[0]!r} to '
                    'compare against (run the same bench again to grow '
                    'the ledger)',
                    file=sys.stderr,
                )
                return 2
        elif len(paths) == 2:
            old_entries = _read_entries(paths[0])
            new_entries = _read_entries(paths[1])
            if not old_entries or not new_entries:
                print(
                    'benchdiff: empty artifact '
                    f'({paths[0]!r} or {paths[1]!r})',
                    file=sys.stderr,
                )
                return 2
            old, new = old_entries[-1], new_entries[-1]
        else:
            print('benchdiff: give at most two artifacts', file=sys.stderr)
            return 2
    except OSError as e:
        print(
            f'benchdiff: cannot read {getattr(e, "filename", None)!r}: '
            f'{e.strerror or e}',
            file=sys.stderr,
        )
        return 2

    result = compare_artifacts(old, new, threshold=args.threshold)
    if args.json:
        print(json.dumps(result, sort_keys=True))
    else:
        _render(result)
    if 'incomparable' in result:
        return 2
    return 1 if result['regressions'] else 0


if __name__ == '__main__':
    sys.exit(main())
