"""End-to-end mesh-serving smoke: front end + replica fan-out, one process.

The ``make mesh-smoke`` gate for mesh-sharded serving (ISSUE 16): one
process hosts a :class:`~socceraction_tpu.serve.RatingService` over an
8-virtual-device CPU mesh behind a :class:`ServingFrontend` unix
socket, and client threads drive it through
:class:`~socceraction_tpu.serve.FrontendClient` — the full client →
front end → flush-lane → replica-device path. Asserted contracts:

1. **Scaling, honestly.** Sustained front-end req/s at 4 replicas vs 1
   replica. On a box with >= 4 physical cores the 4-replica service
   must clear **2x** the 1-replica rate; on fewer cores the lanes
   time-slice the same silicon, so the gate degrades to a
   no-regression floor and PRINTS that the scaling claim was not
   checkable here (a 1-core CI box measuring "4x" would be fiction).
2. **Zero steady-state retraces per replica.** After each service's
   warmup (which compiles every lane's bucket ladder), the measured
   traffic must compile NOTHING: ``compiled_shapes`` frozen and zero
   new ``xla/compiles{fn=pair_probs}``.
3. **Mesh-wide swap + rollback round trip.** ``swap_model`` on the
   4-replica service (every lane warmed before any activates) must
   change the served values to the new version's — bitwise, through
   the front end — and ``rollback_model`` must restore the old
   version's values bitwise.
4. **Fleet scrape merges per-replica serve metrics exactly.** A
   :class:`~socceraction_tpu.obs.fleet.FleetAggregator` scraping this
   process's telemetry endpoint must reproduce ``serve/requests``
   integer-exactly, with the per-lane ``serve/flushes{replica=...}``
   series surviving the wire side by side and summing to the local
   total.

Exit 0 on success; any violated invariant exits non-zero with the
evidence printed. CPU-sized (~a minute); re-execs itself with
``--xla_force_host_platform_device_count=8`` so the mesh exists before
jax initializes. Wired as ``make mesh-smoke`` next to fleet-smoke in CI.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

__all__ = ['main']

N_REPLICAS = 4
N_CLIENTS = 4
DURATION_S = float(os.environ.get('SOCCERACTION_TPU_MESH_SMOKE_SECONDS', 2.0))
HOME = 100


def _reexec_with_mesh() -> None:
    """Re-exec with 8 virtual CPU devices (must precede jax import)."""
    flags = os.environ.get('XLA_FLAGS', '')
    platforms = os.environ.get('JAX_PLATFORMS', '').strip().lower()
    if platforms == 'cpu' and 'xla_force_host_platform_device_count' in flags:
        return
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    env['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8'
    ).strip()
    env.pop('PALLAS_AXON_POOL_IPS', None)
    rc = subprocess.call(
        [sys.executable, os.path.abspath(__file__)], env=env, cwd=REPO
    )
    sys.exit(rc)


def _fit_model(seed: int):
    import numpy as np
    import pandas as pd

    from socceraction_tpu.core.synthetic import synthetic_actions_frame
    from socceraction_tpu.vaep.base import VAEP

    games = (seed, seed + 1)
    frames = [
        synthetic_actions_frame(game_id=g, seed=g, n_actions=300)
        for g in games
    ]
    model = VAEP()
    X, y = [], []
    for g, f in zip(games, frames):
        game = pd.Series({'game_id': g, 'home_team_id': HOME})
        X.append(model.compute_features(game, f))
        y.append(model.compute_labels(game, f))
    np.random.seed(seed)
    model.fit(
        pd.concat(X, ignore_index=True), pd.concat(y, ignore_index=True),
        learner='mlp', tree_params={'hidden': (16,), 'max_epochs': 2},
    )
    return model


def _drive(client_path: str, pool, duration_s: float) -> float:
    """Closed-loop FrontendClient threads; returns sustained req/s."""
    from socceraction_tpu.serve.frontend import FrontendClient, FrontendError

    counts = [0] * N_CLIENTS
    stop = time.perf_counter() + duration_s

    def client(ci: int) -> None:
        c = FrontendClient(client_path)
        k = ci
        while time.perf_counter() < stop:
            frame = pool[k % len(pool)]
            k += 1
            try:
                c.rate(frame, home_team_id=HOME)
            except FrontendError as e:
                if not e.retriable:
                    raise
                continue
            counts[ci] += 1

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=client, args=(ci,)) for ci in range(N_CLIENTS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return sum(counts) / (time.perf_counter() - t0)


def main() -> int:
    _reexec_with_mesh()

    import numpy as np
    import pandas as pd

    from socceraction_tpu.core.synthetic import synthetic_actions_frame
    from socceraction_tpu.obs import REGISTRY
    from socceraction_tpu.obs.endpoint import serve as serve_telemetry
    from socceraction_tpu.obs.fleet import FleetAggregator
    from socceraction_tpu.serve import ModelRegistry, RatingService
    from socceraction_tpu.serve.frontend import FrontendClient, ServingFrontend

    evidence: dict = {'cores': os.cpu_count(), 'duration_s': DURATION_S}
    model_a = _fit_model(0)
    model_b = _fit_model(7)
    pool = [
        synthetic_actions_frame(game_id=40 + i, seed=40 + i, n_actions=120)
        for i in range(8)
    ]
    probe = synthetic_actions_frame(game_id=60, seed=60, n_actions=150)

    def reference(model):
        game = pd.Series({'game_id': 60, 'home_team_id': HOME})
        return model.rate(game, probe).to_numpy()

    ref_a, ref_b = reference(model_a), reference(model_b)
    assert not np.array_equal(ref_a, ref_b), 'v1/v2 models must differ'

    with tempfile.TemporaryDirectory(prefix='mesh-smoke-') as tmp:
        registry = ModelRegistry(os.path.join(tmp, 'models'))
        registry.publish('vaep', '1', model_a)
        registry.publish('vaep', '2', model_b)
        registry.activate('vaep', '1')

        def service(n_replicas: int) -> RatingService:
            return RatingService(
                registry=registry, max_actions=512, max_batch_size=4,
                max_wait_ms=2.0, max_queue=256, n_replicas=n_replicas,
            )

        def steady_leg(n_replicas: int, key: str) -> float:
            sock = os.path.join(tmp, f'{key}.sock')
            with service(n_replicas) as svc:
                with ServingFrontend(svc, unix_path=sock):
                    svc.warmup()
                    shapes = svc.compiled_shapes
                    compiles = REGISTRY.snapshot().value(
                        'xla/compiles', fn='pair_probs'
                    )
                    rate = _drive(sock, pool, DURATION_S)
                    # gate 2: steady traffic compiles nothing on any lane
                    assert svc.compiled_shapes == shapes, (
                        f'{key}: steady-state retrace '
                        f'({shapes} -> {svc.compiled_shapes} shapes)'
                    )
                    new_compiles = REGISTRY.snapshot().value(
                        'xla/compiles', fn='pair_probs'
                    ) - compiles
                    assert new_compiles == 0, (
                        f'{key}: {new_compiles} pair_probs compiles during '
                        'steady traffic'
                    )
                    health = svc.health()
                    assert health['status'] == 'ok', health
                    if n_replicas > 1:
                        assert health['replicas']['sick'] == [], health
            evidence[f'req_per_sec_{key}'] = round(rate, 1)
            return rate

        rate1 = steady_leg(1, 'r1')
        rate4 = steady_leg(N_REPLICAS, 'r4')

        # gate 1: the scaling claim, only where it is measurable
        cores = os.cpu_count() or 1
        if cores >= N_REPLICAS:
            assert rate4 >= 2.0 * rate1, (
                f'{N_REPLICAS} replicas sustained {rate4:.1f} req/s vs '
                f'{rate1:.1f} at 1 replica on {cores} cores — expected >= 2x'
            )
            evidence['scaling_gate'] = '>=2x enforced'
        else:
            assert rate4 >= 0.4 * rate1, (
                f'replica fan-out REGRESSED throughput on {cores} core(s): '
                f'{rate4:.1f} req/s at {N_REPLICAS} replicas vs {rate1:.1f} '
                'at 1 — overlap bookkeeping must not cost >60%'
            )
            evidence['scaling_gate'] = (
                f'NOT CHECKABLE: {cores} core(s) < {N_REPLICAS} replicas — '
                'lanes time-slice one core; enforced no-regression floor only'
            )
            print(
                f'mesh-smoke NOTE: only {cores} physical core(s); the >=2x '
                'scaling gate needs >= 4 — ran the no-regression floor instead'
            )

        # gates 3+4 on a fresh 4-replica service under a live endpoint
        sock = os.path.join(tmp, 'swap.sock')
        with service(N_REPLICAS) as svc:
            with ServingFrontend(svc, unix_path=sock):
                svc.warmup()
                client = FrontendClient(sock)
                out1 = client.rate(probe, home_team_id=HOME).to_numpy()
                assert np.array_equal(out1, ref_a), 'v1 served wrong values'

                # gate 3: mesh-wide swap (every lane warmed before any
                # activates) then rollback, bitwise through the front end
                assert svc.swap_model('vaep', '2') == ('vaep', '2')
                out2 = client.rate(probe, home_team_id=HOME).to_numpy()
                assert np.array_equal(out2, ref_b), (
                    'post-swap values are not version 2\'s'
                )
                assert svc.rollback_model() == ('vaep', '1')
                out3 = client.rate(probe, home_team_id=HOME).to_numpy()
                assert np.array_equal(out3, ref_a), (
                    'post-rollback values are not version 1\'s'
                )
                evidence['swap_rollback'] = 'bitwise round trip ok'

                # gate 4: the fleet plane merges this process's
                # per-replica serve metrics integer-exactly
                local = REGISTRY.snapshot()
                with serve_telemetry(
                    telemetry=svc.telemetry(replica='mesh-front'),
                    unix_path=os.path.join(tmp, 'telemetry.sock'),
                ) as endpoint:
                    agg = FleetAggregator(
                        {'mesh-front': endpoint.address}, stale_after_s=30.0
                    )
                    assert agg.scrape() == {'mesh-front': True}
                    fleet = agg.aggregate()
                assert fleet.status == 'ok', fleet.status
                merged = fleet.typed()
                local = REGISTRY.snapshot()
                assert (
                    merged.value('serve/requests', kind='rate')
                    == local.value('serve/requests', kind='rate')
                    > 0
                ), 'fleet merge lost serve/requests'
                lanes_local = lanes_merged = 0
                for rid in svc.replica_ids:
                    for snap, tally in ((local, 'local'), (merged, 'merged')):
                        n = sum(
                            int(snap.value(
                                'serve/flushes', reason=reason, replica=rid
                            ))
                            for reason in ('full', 'deadline')
                        )
                        if tally == 'local':
                            lanes_local += n
                        else:
                            lanes_merged += n
                assert lanes_local == lanes_merged > 0, (
                    f'per-replica flush series did not survive the wire '
                    f'exactly: local={lanes_local} merged={lanes_merged}'
                )
                evidence['fleet_merge'] = {
                    'serve_requests': int(merged.value('serve/requests', kind='rate')),
                    'replica_flushes': lanes_merged,
                }

    print('mesh-smoke OK ' + json.dumps(evidence, sort_keys=True))
    return 0


if __name__ == '__main__':
    sys.exit(main())
