"""End-to-end capacity smoke: a warm serve path + a re-exec'd cold start.

The ``make capacity-smoke`` gate for the capacity observatory, in two
halves:

**Warm half** — fit a tiny VAEP, publish it through a
:class:`~socceraction_tpu.serve.ModelRegistry` (so the HBM residency
ledger's ``registry`` owner claims the warm version's bytes), then
serve a short request sequence through a live
:class:`~socceraction_tpu.serve.RatingService` under a ``RunLog`` and
assert the observatory measured it:

- the live roofline recorded the serve loop: ``perf/dispatches`` and
  the achieved-rate gauges (``perf/achieved_flops``/``achieved_bytes``
  — the CPU-honest half; ``perf/roofline_frac`` must be ABSENT on CPU,
  where no device peak is defined) plus a ``perf/device_idle_frac``
  sample for the flusher loop;
- the residency ledger attributes the warm model (``mem/owned_bytes
  {owner="registry"}`` > 0) and ``residency_report()`` reconciles
  against the live-array census with the unattributed remainder
  accounting for exactly the census bytes no owner claimed;
- ``health()`` carries the capacity block;
- the sampled perf instrumentation kept the serve path's zero
  steady-state retraces (compiled-shape plateau across the measured
  requests);
- ``obsctl capacity`` round-trips BOTH ways: over the closed run log's
  embedded snapshot, and live in-process (census included).

**Cold half** — re-exec ``bench.py --cold-start`` (the cold vs
cache-hit vs AOT-shipped matrix of clean-CPU children) with the ledger
redirected to a scratch file, and assert the artifact contract: one
ledger entry per tier, every startup phase present (import /
registry_load / device_upload / aot_deserialize / ladder_compile /
first_dispatch), each phase sum bounded by its wall, and the AOT tier's
wall strictly below the cold one.

The matrix's AOT tier *is* the ISSUE 13 CI leg — the bench publishes
the registry version with serialized executables and re-execs a clean
child against it — so its contract is asserted here off the ledger
entry that child wrote: ``ladder_compile ≈ 0`` and
``serve/aot_loads{outcome="hit"}`` ≥ the ladder rung count (the child
reports its counter into the artifact as ``aot_hits``), with no extra
child re-exec of our own.

Exit 0 on success; any violated invariant is a non-zero exit with the
evidence printed. CPU-sized, but the cold half re-execs several clean
Python processes — minutes, not seconds.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

__all__ = ['main']

#: requests served in the warm half (≥2 so the idle detector has gaps)
N_REQUESTS = 6


def _warm_half(problems: list) -> None:
    import numpy as np
    import pandas as pd

    from socceraction_tpu.core.synthetic import synthetic_actions_frame
    from socceraction_tpu.obs import REGISTRY, RunLog
    from socceraction_tpu.obs.residency import owned_bytes, residency_report
    from socceraction_tpu.serve import ModelRegistry, RatingService
    from socceraction_tpu.vaep.base import VAEP
    from tools.obsctl import main as obsctl_main

    frame = synthetic_actions_frame(game_id=0, seed=0, n_actions=120)
    model = VAEP()
    game = pd.Series({'game_id': 0, 'home_team_id': 100})
    np.random.seed(0)
    model.fit(
        model.compute_features(game, frame),
        model.compute_labels(game, frame),
        learner='mlp',
        tree_params={'hidden': (8,), 'max_epochs': 2},
    )

    with tempfile.TemporaryDirectory(prefix='capacity-smoke-') as tmp:
        registry = ModelRegistry(os.path.join(tmp, 'registry'))
        registry.publish('capacity', '1', model)
        registry.activate('capacity', '1')
        _name, _version, warm_model = registry.active()
        if owned_bytes().get('registry', 0) <= 0:
            problems.append(
                'the residency ledger did not claim the warm model '
                f'(owned_bytes={owned_bytes()})'
            )

        runlog_path = os.path.join(tmp, 'obs.jsonl')
        with RunLog(runlog_path, config={'smoke': 'capacity'}):
            with RatingService(
                warm_model, max_actions=256, max_batch_size=4, max_wait_ms=1.0
            ) as service:
                service.warmup()
                # one measured request, then the plateau window: any
                # steady-state retrace past this point is a regression
                service.rate_sync(frame, home_team_id=100, timeout=120)
                shapes_before = service.compiled_shapes
                for _ in range(N_REQUESTS - 1):
                    service.rate_sync(frame, home_team_id=100, timeout=120)
                if service.compiled_shapes != shapes_before:
                    problems.append(
                        'steady-state retrace: compiled shapes moved '
                        f'{shapes_before} -> {service.compiled_shapes} '
                        'across the measured requests'
                    )
                health = service.health()
            report = residency_report(top=5)

        # -- the live roofline measured the serve loop -------------------
        snap = REGISTRY.snapshot()
        if not snap.value('perf/dispatches', fn='pair_probs', bucket='1'):
            problems.append('no perf/dispatches recorded for the serve loop')
        if snap.series('perf/achieved_flops', fn='pair_probs', bucket='1') is None:
            problems.append('no perf/achieved_flops gauge for the serve loop')
        if snap.series('perf/achieved_bytes', fn='pair_probs', bucket='1') is None:
            problems.append('no perf/achieved_bytes gauge for the serve loop')
        if snap.series('perf/device_idle_frac', fn='pair_probs') is None:
            problems.append('no perf/device_idle_frac for the flusher loop')
        # no device peak is defined for CPU: a roofline fraction here
        # would be noise presented as signal — its absence IS the contract
        if snap.series('perf/roofline_frac', fn='pair_probs', bucket='1'):
            problems.append('perf/roofline_frac recorded on CPU (no peak)')

        # -- health carries the capacity block ---------------------------
        capacity = health.get('capacity') or {}
        if 'pair_probs' not in (capacity.get('perf') or {}):
            problems.append(f'health() capacity block incomplete: {capacity}')
        if capacity.get('owned_bytes', {}).get('registry', 0) <= 0:
            problems.append(
                'health() capacity block does not attribute the warm model'
            )

        # -- the ledger reconciles against the census --------------------
        if not report.get('census_supported'):
            problems.append('census unsupported with jax loaded')
        else:
            accounted = (
                report['owned_total_bytes']
                + report['unattributed_bytes']
                - report['over_attributed_bytes']
            )
            if accounted != report['census_total_bytes']:
                problems.append(
                    f'residency reconciliation does not balance: {report}'
                )

        # -- obsctl capacity round-trips, post-mortem and live -----------
        for argv, source in (
            (['capacity', runlog_path, '--json'], 'runlog'),
            (['capacity', '--json'], 'live'),
        ):
            out = io.StringIO()
            with contextlib.redirect_stdout(out):
                rc = obsctl_main(argv)
            if rc != 0:
                problems.append(f'obsctl capacity ({source}) exited {rc}')
                continue
            summary = json.loads(out.getvalue())
            fns = {row.get('fn') for row in summary.get('perf', [])}
            if 'pair_probs' not in fns:
                problems.append(
                    f'obsctl capacity ({source}) lost the serve loop: {fns}'
                )
            owners = summary.get('owned_bytes') or {}
            if not owners.get('registry'):
                problems.append(
                    f'obsctl capacity ({source}) lost the registry owner: '
                    f'{owners}'
                )


def _cold_half(problems: list) -> None:
    from bench import COLD_START_PHASES, COLD_START_TIER_METRICS

    with tempfile.TemporaryDirectory(prefix='capacity-smoke-cold-') as tmp:
        ledger = os.path.join(tmp, 'ledger.jsonl')
        env = dict(os.environ)
        # the env var names the ledger DIRECTORY; bench writes
        # <dir>/ledger.jsonl inside it
        env['SOCCERACTION_TPU_BENCH_HISTORY'] = tmp
        # SOCCERACTION_TPU_COLDSTART_DEADLINE is bench's PER-CHILD
        # budget; the matrix runs four children plus the parent's fit +
        # AOT export, so the outer timeout scales from it instead of
        # reusing it verbatim (which would kill a healthy matrix whose
        # children are each inside budget)
        per_child = float(os.environ.get(
            'SOCCERACTION_TPU_COLDSTART_DEADLINE', 300
        ))
        try:
            proc = subprocess.run(
                [
                    sys.executable,
                    os.path.join(REPO, 'bench.py'),
                    '--cold-start',
                ],
                env=env,
                cwd=REPO,
                capture_output=True,
                text=True,
                timeout=4 * per_child + 240,
            )
        except subprocess.TimeoutExpired as e:
            problems.append(f'bench.py --cold-start timed out: {e}')
            return
        if proc.returncode != 0:
            problems.append(
                f'bench.py --cold-start exited {proc.returncode}: '
                f'{proc.stderr[-2000:]}'
            )
            return
        if not os.path.isfile(ledger):
            problems.append('cold start produced no ledger entry')
            return
        with open(ledger, encoding='utf-8') as f:
            entries = [json.loads(line) for line in f if line.strip()]
        # the full matrix lands: one ledger entry per warm tier, each
        # with the complete phase breakdown bounded by its wall
        by_metric = {e.get('metric'): e for e in entries}
        for tier, metric in COLD_START_TIER_METRICS.items():
            entry = by_metric.get(metric)
            if entry is None:
                problems.append(
                    f'no {metric} ledger entry (tier {tier}) in '
                    f'{sorted(by_metric)}'
                )
                continue
            missing = set(COLD_START_PHASES) - set(
                entry.get('phase_seconds', {})
            )
            if missing:
                problems.append(
                    f'[{tier}] cold-start phases missing from ledger: '
                    f'{missing}'
                )
            if entry['phase_total_s'] > entry['value'] + 1e-6:
                problems.append(
                    f'[{tier}] phase sum {entry["phase_total_s"]}s exceeds '
                    f'the measured wall {entry["value"]}s'
                )
        cold = by_metric.get('cold_start_seconds')
        aot = by_metric.get('cold_start_aot_seconds')
        if cold and aot:
            if aot['value'] >= cold['value']:
                problems.append(
                    f'AOT-shipped wall {aot["value"]}s not below the '
                    f'cold wall {cold["value"]}s'
                )
            # the ISSUE 13 AOT leg, read off the ledger the matrix's
            # published-with-artifacts clean child just wrote (no extra
            # child re-exec): the executables deserialized
            # (outcome=hit), every rung's programs were hit-counted
            # (serve/aot_loads{outcome=hit} ≥ ladder rungs — the child
            # reports its counter into the artifact), and the ladder
            # compile collapsed to ≈ 0
            if (aot.get('aot') or {}).get('outcome') != 'hit':
                problems.append(
                    f'AOT tier did not deserialize: {aot.get("aot")}'
                )
            ladder_rungs = 3  # bench's matrix exports ladder (1, 2, 4)
            if int(aot.get('aot_hits', 0)) < ladder_rungs:
                problems.append(
                    f'aot_loads{{outcome=hit}} = {aot.get("aot_hits")} < '
                    f'ladder rung count {ladder_rungs}'
                )
            ladder_compile = (
                aot.get('phase_seconds', {}).get('ladder_compile')
            )
            if ladder_compile is None or ladder_compile > 0.5:
                problems.append(
                    f'AOT tier ladder_compile = {ladder_compile}s, '
                    'expected ~0 (deserialized executables must cover '
                    'the ladder)'
                )


def main() -> int:
    """Drive the warm + cold capacity paths; returns an exit code."""
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    problems: list = []
    _warm_half(problems)
    _cold_half(problems)
    if problems:
        for p in problems:
            print(f'capacity-smoke: FAIL - {p}')
        return 1
    print(
        'capacity-smoke: OK - roofline + residency + cold-start matrix '
        '+ AOT deserialize verified'
    )
    return 0


if __name__ == '__main__':
    sys.exit(main())
