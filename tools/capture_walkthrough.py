"""Capture executed walkthrough outputs — the repo's analog of the
reference's executed notebook cells.

The reference ships 9 notebooks WITH stored cell outputs
(``/root/reference/public-notebooks/*.ipynb``), which act as its
de-facto acceptance record: a reader sees real numbers without running
anything. This tool runs the walkthrough chapters
(``docs/walkthrough/*.py``) in order against a fresh synthetic store and
commits each chapter's real stdout to ``docs/walkthrough/outputs/<n>.txt``.

``tests/test_walkthrough.py`` re-runs the chapters and diffs the
*normalized* output (numbers → ``#``, absolute paths → ``<path>``,
whitespace stripped) against these files, so the committed record is
drift-checked: wording, section structure and table shapes are pinned
while timings and other volatile literals are free to vary.

Regenerate with ``make walkthrough-outputs`` after changing a chapter or
the synthetic generator.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import tempfile

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WT = os.path.join(_ROOT, 'docs', 'walkthrough')
_OUT = os.path.join(_WT, 'outputs')

CHAPTERS = [
    '1_load_and_convert.py',
    '2_features_and_labels.py',
    '3_train_probability_models.py',
    '4_rate_and_rank_players.py',
    # chapter 5 runs without --processes: the two-process tier is
    # covered (and time-bounded) by tests/test_distributed.py
    '5_scale_out.py',
    '6_atomic_pipeline.py',
]


def chapter_args(store: str, ckpt: str) -> dict:
    """Per-chapter CLI args (single source shared with the test)."""
    return {
        '1_load_and_convert.py': ['--store', store],
        '2_features_and_labels.py': ['--store', store],
        '3_train_probability_models.py': ['--store', store, '--checkpoint', ckpt],
        '4_rate_and_rank_players.py': ['--store', store, '--checkpoint', ckpt],
        '5_scale_out.py': [],
        '6_atomic_pipeline.py': ['--store', store],
    }


_NUM = re.compile(r'-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?')
# Path-like only: a leading slash not glued to a word (so 'actions/sec'
# or 'scores/concedes' prose stays pinned) followed by at least one more
# /-separated segment — matching real filesystem paths, not units.
_PATH = re.compile(r'(?<![\w])/(?:[\w.\-]+/)+[\w.\-]*')


def normalize(text: str) -> list:
    """The drift-checked view of a chapter's stdout.

    Absolute paths → ``<path>``, numeric literals → ``#``, whitespace
    runs collapsed (number widths drive pandas column alignment, so
    alignment is as volatile as the numbers), blank lines dropped. What
    remains — wording, section structure, table columns, label text —
    is what the test pins.
    """
    out = []
    for line in text.splitlines():
        line = _PATH.sub('<path>', line)
        line = _NUM.sub('#', line)
        line = re.sub(r'\s+', ' ', line).strip()
        if line:
            out.append(line)
    return out


def run_chapter(script: str, store: str, ckpt: str, timeout: int = 560) -> str:
    """Run one chapter; return its stdout (raises on nonzero exit)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_WT, script)]
        + chapter_args(store, ckpt)[script],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=_ROOT,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f'{script} failed (rc={proc.returncode}):\n'
            f'{proc.stdout[-3000:]}\n{proc.stderr[-3000:]}'
        )
    return proc.stdout


def main() -> int:
    os.makedirs(_OUT, exist_ok=True)
    with tempfile.TemporaryDirectory(prefix='walkthrough_capture_') as tmp:
        store = os.path.join(tmp, 'store.h5')
        ckpt = os.path.join(tmp, 'vaep_ckpt')
        for script in CHAPTERS:
            out = run_chapter(script, store, ckpt)
            dest = os.path.join(_OUT, script.replace('.py', '.txt'))
            with open(dest, 'w', encoding='utf-8') as f:
                f.write(out)
            print(f'{script}: {len(out.splitlines())} lines -> {dest}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
